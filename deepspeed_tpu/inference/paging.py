"""Host-side page management for the paged KV cache.

The paged layout (inference/kv_cache.py ``PagedKVCache``) splits the KV
pool into fixed-size pages; what maps a sequence's logical positions
onto physical pages lives HERE, on the host, because allocation is
control flow, not math:

  * :class:`PageAllocator` — free list + per-page refcounts. Page 0 is
    the reserved GARBAGE page: it is never handed out, padded/invalid
    writes inside the jitted programs are redirected to it, and page
    tables of retired slots point at it. Refcounts > 1 mean the page is
    shared (prefix sharing); writes into a shared page must fork it
    first (:meth:`PageAllocator.fork` + a device-side page copy by the
    engine) — classic copy-on-write.
  * :class:`PrefixCache` — hash-matched common prefixes. Keys chain per
    FULL page (vLLM's block-hash discipline): page j's key hashes
    (key_{j-1}, page-j tokens), so a hit at depth j certifies the whole
    prefix. The cache holds its own reference on every registered page,
    so retiring the sequence that populated it does not free the pages;
    LRU eviction drops that reference.
  * :func:`plan_chunks` — chunked-prefill schedule with the slot-layout
    write-safety guarantee (start + bucket never exceeds max_seq, or the
    clamped ``dynamic_update_slice`` would shift the write window down
    over live positions).
"""
from collections import OrderedDict

GARBAGE_PAGE = 0


class PagePoolExhausted(Exception):
    """Raised by strict allocation; the scheduler's admission/preemption
    paths use :meth:`PageAllocator.can_alloc` instead of catching."""


class PageAllocator:
    """Refcounted allocator over physical pages ``1 .. num_pages``.

    ``num_pages`` counts USABLE pages; the physical buffer has one more
    (the garbage page 0). Invariants (pinned by tests/unit/
    test_serving.py): a page is either free (refcount 0, in the free
    list) or held (refcount >= 1); alloc never returns page 0; free of
    a free page raises; every retire path ends with the sequence's
    pages back at their pre-admission refcounts.
    """

    def __init__(self, num_pages):
        assert num_pages >= 1, "page pool needs at least one usable page"
        self.num_pages = int(num_pages)
        # LIFO free list: recently-freed pages are re-used first (their
        # cache lines / HBM pages are warm)
        self._free = list(range(self.num_pages, 0, -1))
        self._refs = [0] * (self.num_pages + 1)

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.num_pages - len(self._free)

    def can_alloc(self, n):
        return len(self._free) >= n

    def refcount(self, page):
        return self._refs[page]

    def alloc(self):
        """-> one fresh page (refcount 1). Raises PagePoolExhausted."""
        if not self._free:
            raise PagePoolExhausted(
                "KV page pool exhausted ({} pages)".format(self.num_pages))
        page = self._free.pop()
        assert self._refs[page] == 0
        self._refs[page] = 1
        return page

    def ref(self, page):
        """Add a reference to a held page (prefix sharing / fork source)."""
        assert page != GARBAGE_PAGE, "cannot reference the garbage page"
        assert self._refs[page] >= 1, \
            "ref of unheld page {}".format(page)
        self._refs[page] += 1

    def free(self, page):
        """Drop one reference; the page returns to the pool at zero."""
        if page == GARBAGE_PAGE:
            return
        assert self._refs[page] >= 1, \
            "double free of page {}".format(page)
        self._refs[page] -= 1
        if self._refs[page] == 0:
            self._free.append(page)

    def fork(self, page):
        """Copy-on-write fork: if ``page`` is shared (refcount > 1),
        allocate a fresh page, move one reference onto it, and return
        ``(new_page, True)`` — the CALLER must copy the page's device
        contents before any write. Unshared pages return unchanged."""
        if self._refs[page] <= 1:
            return page, False
        new = self.alloc()
        self._refs[page] -= 1
        return new, True

    def stats(self):
        return {"num_pages": self.num_pages,
                "pages_in_use": self.pages_in_use,
                "occupancy": (self.pages_in_use / self.num_pages
                              if self.num_pages else 0.0)}


class PrefixCache:
    """Hash-matched shared prompt prefixes at full-page granularity.

    ``match(tokens)`` walks the prompt's full pages left to right
    through the chained-hash map and returns the longest registered
    run of pages; ``register(tokens, pages)`` records a prompt's full
    pages after its prefill. Registered pages carry one cache-owned
    reference (taken via the allocator) so sequence retirement cannot
    free them out from under a future hit; eviction (LRU over entries,
    capped at ``max_entries`` pages total) releases that reference.

    Matching never covers the whole prompt: the caller caps the match
    so at least one prompt token still runs through the model (logits
    for the first sampled token have to come from somewhere).
    """

    def __init__(self, allocator, page_size, max_entries=1024):
        self.allocator = allocator
        self.page_size = int(page_size)
        self.max_entries = int(max_entries)
        # chain key -> page id, LRU ordered (move_to_end on hit)
        self._entries = OrderedDict()
        self.lookups = 0
        self.hits = 0          # lookups that matched >= 1 page
        self.hit_pages = 0     # total pages mapped from the cache
        self.tokens_saved = 0  # prompt tokens NOT re-embedded

    def _chain_keys(self, tokens, namespace=None):
        """Chained hash per full page of ``tokens``. A non-None
        ``namespace`` (e.g. a tenant's adapter id) seeds the chain, so
        namespaced entries never collide with the base chain or with
        other namespaces — tenants cannot cross-hit each other's
        prompts."""
        keys = []
        key = None if namespace is None else ("ns", namespace)
        ps = self.page_size
        for j in range(len(tokens) // ps):
            key = hash((key, tuple(tokens[j * ps:(j + 1) * ps])))
            keys.append(key)
        return keys

    def match(self, tokens, max_tokens, skip_pages=0, count_lookup=True,
              namespace=None):
        """-> (new_pages list, new_token_count) for the longest
        registered full-page prefix of ``tokens`` BEYOND the first
        ``skip_pages`` pages (already held by the caller), capped at
        ``max_tokens`` total. Takes ONE allocator reference per
        returned page (the caller's page table now holds them).

        Two call phases per request: admission (``count_lookup`` — one
        lookup per request) and first-chunk extension (skip = what
        admission matched, no second lookup — a same-step burst sibling
        may have registered more pages in between; a request counts as
        ONE hit across both phases)."""
        if count_lookup:
            self.lookups += 1
        pages = []
        cap_pages = max(0, int(max_tokens)) // self.page_size
        for key in self._chain_keys(tokens,
                                    namespace=namespace)[:cap_pages]:
            page = self._entries.get(key)
            if page is None:
                break
            self._entries.move_to_end(key)
            pages.append(page)
        new = pages[skip_pages:]
        for page in new:
            self.allocator.ref(page)
        if new:
            if count_lookup or skip_pages == 0:
                self.hits += 1
            self.hit_pages += len(new)
            self.tokens_saved += len(new) * self.page_size
        return new, len(new) * self.page_size

    def register(self, tokens, pages, namespace=None):
        """Record a prompt's full pages. ``pages[j]`` must hold tokens
        ``[j*ps, (j+1)*ps)``; entries already present are skipped (the
        existing shared page wins — the new duplicate stays owned by
        its sequence alone)."""
        for j, key in enumerate(self._chain_keys(tokens,
                                                 namespace=namespace)):
            if j >= len(pages):
                break
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            self.allocator.ref(pages[j])
            self._entries[key] = pages[j]
            while len(self._entries) > self.max_entries:
                _, evicted = self._entries.popitem(last=False)
                self.allocator.free(evicted)

    def unmatch(self, pages, counted_lookup=True):
        """Roll back one :meth:`match` whose admission failed: release
        the taken page references AND un-count the stats — a pool-full
        request retried every scheduler step would otherwise inflate
        hits/tokens_saved with savings that never happened."""
        for page in pages:
            self.allocator.free(page)
        if pages:
            self.hits -= 1
            self.hit_pages -= len(pages)
            self.tokens_saved -= len(pages) * self.page_size
        if counted_lookup:
            self.lookups -= 1

    def evict(self, n_needed):
        """Drop LRU entries (releasing the cache's page references)
        until the allocator can hand out ``n_needed`` pages or the
        cache is empty. Pages still referenced by live sequences just
        lose the cache's claim — they free when their sequences do."""
        while self._entries and not self.allocator.can_alloc(n_needed):
            _, page = self._entries.popitem(last=False)
            self.allocator.free(page)

    @property
    def hit_rate(self):
        return self.hits / self.lookups if self.lookups else 0.0

    def stats(self):
        return {"lookups": self.lookups, "hits": self.hits,
                "hit_rate": round(self.hit_rate, 4),
                "shared_pages": self.hit_pages,
                "tokens_saved": self.tokens_saved,
                "entries": len(self._entries)}

    def clear(self):
        for page in self._entries.values():
            self.allocator.free(page)
        self._entries.clear()


def plan_chunks(n_tokens, chunk_tokens, bucket_for, max_seq, start=0,
                max_chunk=None):
    """Chunked-prefill schedule: ``[(start, length), ...]`` covering
    ``[start, start + n_tokens)`` in pieces of at most ``chunk_tokens``.
    ``max_chunk`` (the largest prefill bucket) caps the chunk size
    regardless of config: a preemption-resume context longer than every
    bucket always chunks, whatever ``prefill_chunk_tokens`` says.

    Safety: the slot layout writes each chunk with a
    ``dynamic_update_slice`` of the full PADDED bucket at ``start`` —
    XLA clamps an out-of-range start so ``start + bucket > max_seq``
    would silently shift the write DOWN over live positions. A plan
    with such a chunk is merged back into one unchunked prefill when a
    bucket covers the whole span; otherwise the chunked plan stands
    (the paged layout's per-token masked scatter is safe by
    construction, and the slot path keeps a LOUD overrun assert)."""
    if max_chunk is not None:
        chunk_tokens = min(chunk_tokens or max_chunk, max_chunk)
    if not chunk_tokens or n_tokens <= chunk_tokens:
        return [(start, n_tokens)]
    chunks, pos, violated = [], 0, False
    while pos < n_tokens:
        ln = min(chunk_tokens, n_tokens - pos)
        violated = violated or start + pos + bucket_for(ln) > max_seq
        chunks.append((start + pos, ln))
        pos += ln
    if violated and n_tokens <= (max_chunk or n_tokens):
        return [(start, n_tokens)]
    return chunks
