"""KV page-slice handoff: the wire format between prefill and decode.

A *page slice* is one finished request's KV state, lifted out of the
prefill engine's paged pool: the page payloads (``(n_pages, layers,
heads, page_size, d_head)`` K and V stacks, gathered by physical page
id) plus the table metadata a decode engine needs to resume — resident
token count, the pending first sampled token, and the context tokens
(for prefix registration and preemption-recompute on the decode side).

Two codecs, one container:

  * **fp path** (default): the raw array bytes move verbatim — the
    import is BITWISE identical to the export, so a greedy stream
    through prefill → handoff → decode reproduces the single-engine
    paged stream byte-for-byte (the oracle the quantized path is
    judged against);
  * **int8 path** (opt-in, ``inference.fleet.handoff_quantize``): K/V
    ride the PR 3 blockwise codec (runtime/comm/quantize.py) — ~4x
    less wire below fp32. Tolerance contract (documented in
    docs/inference.md): each reconstructed lane differs from the
    original by at most ``0.5 * blockwise_absmax / 127`` plus rounding
    (the symmetric-int8 quantization step), so downstream decode
    drifts within ordinary quantization noise.

Container: ``b"DSKV"`` magic, u16 version, u32 header length, a JSON
header (segment table, shapes, dtypes, CRC32 + byte count of the
payload), then the concatenated payload bytes. Torn or truncated
payloads are rejected LOUDLY (:class:`HandoffError`): a short read
fails the length check, a corrupted one fails the CRC — never a
silently wrong cache.
"""
import json
import struct
import zlib

import numpy as np

MAGIC = b"DSKV"
VERSION = 1

_HEAD = struct.Struct(">4sHI")   # magic, version, header byte length

DEFAULT_HANDOFF_BLOCK = 256


class HandoffError(Exception):
    """A page-slice payload that cannot be trusted: bad magic, version
    skew, truncation, or checksum mismatch. Always raised loudly —
    importing a torn slice would poison the decode cache silently."""


class PageSlice:
    """One request's exported KV state (host-side numpy)."""

    __slots__ = ("k_pages", "v_pages", "page_size", "length",
                 "pending_token", "context", "trace_id")

    def __init__(self, k_pages, v_pages, page_size, length,
                 pending_token, context, trace_id=None):
        self.k_pages = k_pages        # (n_pages, layers, heads, ps, dh)
        self.v_pages = v_pages
        self.page_size = int(page_size)
        self.length = int(length)     # tokens resident in the pages
        self.pending_token = int(pending_token)
        self.context = [int(t) for t in context]
        # the request's span trace_id, carried across the handoff so
        # prefill + decode read as ONE trace (None when spans are off)
        self.trace_id = None if trace_id is None else str(trace_id)

    @property
    def n_pages(self):
        return self.k_pages.shape[0]

    @property
    def nbytes(self):
        return self.k_pages.nbytes + self.v_pages.nbytes


def export_slice(engine, slot, context, pending_token, trace_id=None):
    """Lift ``slot``'s live pages out of a paged engine's pool into a
    host :class:`PageSlice`. The slot keeps its pages (the caller
    frees it after a successful handoff — export never mutates)."""
    assert engine.kv_layout == "paged", \
        "page-slice handoff needs kv_layout 'paged', engine runs " \
        "{!r}".format(engine.kv_layout)
    n_pages = int(engine.page_counts[slot])
    length = int(engine.lengths[slot])
    assert n_pages >= 1 and length >= 1, \
        "slot {} holds no live pages to export".format(slot)
    page_ids = np.asarray(engine.page_tables[slot, :n_pages], np.int32)
    k = np.asarray(engine.kv.k[page_ids])
    v = np.asarray(engine.kv.v[page_ids])
    return PageSlice(k, v, engine.page_size, length, pending_token,
                     context, trace_id=trace_id)


def serialize_slice(sl, quantize=False, block_size=DEFAULT_HANDOFF_BLOCK):
    """:class:`PageSlice` -> container bytes (fp verbatim, or the
    blockwise-int8 codec when ``quantize``)."""
    segments = []     # (name, dtype str, shape list, bytes)
    if quantize:
        from ...runtime.comm.quantize import quantize_blockwise
        import jax.numpy as jnp
        for name, arr in (("k", sl.k_pages), ("v", sl.v_pages)):
            q, scales = quantize_blockwise(jnp.asarray(arr), block_size)
            q, scales = np.asarray(q), np.asarray(scales)
            segments.append((name + "_q", q))
            segments.append((name + "_scales", scales))
    else:
        segments.append(("k", sl.k_pages))
        segments.append(("v", sl.v_pages))
    payload = b"".join(np.ascontiguousarray(a).tobytes()
                       for _, a in segments)
    header = {
        "page_size": sl.page_size,
        "length": sl.length,
        "pending_token": sl.pending_token,
        "context": sl.context,
        "trace_id": sl.trace_id,
        "shape": list(sl.k_pages.shape),
        "dtype": np.dtype(sl.k_pages.dtype).name,
        "quantized": bool(quantize),
        "block_size": int(block_size),
        "segments": [{"name": name, "dtype": np.dtype(a.dtype).name,
                      "shape": list(a.shape), "nbytes": int(a.nbytes)}
                     for name, a in segments],
        "payload_nbytes": len(payload),
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    header_bytes = json.dumps(header, sort_keys=True).encode("utf-8")
    return _HEAD.pack(MAGIC, VERSION, len(header_bytes)) + \
        header_bytes + payload


def deserialize_slice(data):
    """Container bytes -> :class:`PageSlice`, with LOUD rejection of
    anything torn: magic/version skew, truncated header or payload,
    CRC mismatch all raise :class:`HandoffError`."""
    if len(data) < _HEAD.size:
        raise HandoffError(
            "payload of {} bytes is shorter than the {}-byte container "
            "head".format(len(data), _HEAD.size))
    magic, version, header_len = _HEAD.unpack_from(data)
    if magic != MAGIC:
        raise HandoffError(
            "bad magic {!r} (want {!r}) — not a KV page slice".format(
                magic, MAGIC))
    if version != VERSION:
        raise HandoffError(
            "page-slice version {} unsupported (this codec speaks "
            "{})".format(version, VERSION))
    body = data[_HEAD.size:]
    if len(body) < header_len:
        raise HandoffError(
            "truncated header: {} of {} bytes present".format(
                len(body), header_len))
    try:
        header = json.loads(body[:header_len].decode("utf-8"))
    except ValueError as err:
        raise HandoffError("corrupt header JSON: {}".format(err))
    payload = body[header_len:]
    if len(payload) != header["payload_nbytes"]:
        raise HandoffError(
            "truncated payload: {} of {} bytes present".format(
                len(payload), header["payload_nbytes"]))
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != header["payload_crc32"]:
        raise HandoffError(
            "payload checksum mismatch (crc32 {:#010x}, header says "
            "{:#010x}) — torn or corrupted handoff".format(
                crc, header["payload_crc32"]))
    arrays, off = {}, 0
    for seg in header["segments"]:
        n = seg["nbytes"]
        arrays[seg["name"]] = np.frombuffer(
            payload[off:off + n],
            dtype=np.dtype(seg["dtype"])).reshape(seg["shape"])
        off += n
    shape = tuple(header["shape"])
    dtype = np.dtype(header["dtype"])
    if header["quantized"]:
        from ...runtime.comm.quantize import dequantize_blockwise
        import jax.numpy as jnp
        size = int(np.prod(shape))
        k = np.asarray(dequantize_blockwise(
            jnp.asarray(arrays["k_q"]), jnp.asarray(arrays["k_scales"]),
            size)).reshape(shape).astype(dtype)
        v = np.asarray(dequantize_blockwise(
            jnp.asarray(arrays["v_q"]), jnp.asarray(arrays["v_scales"]),
            size)).reshape(shape).astype(dtype)
    else:
        k = arrays["k"].astype(dtype, copy=False).reshape(shape)
        v = arrays["v"].astype(dtype, copy=False).reshape(shape)
    # tolerant get: version-1 slices written before trace propagation
    # simply carry no trace_id
    return PageSlice(k, v, header["page_size"], header["length"],
                     header["pending_token"], header["context"],
                     trace_id=header.get("trace_id"))


def import_slice(engine, slot, sl):
    """Map a :class:`PageSlice` into ``slot`` of a (different) paged
    engine: allocate pages, scatter the payloads into the pool, point
    the slot's table at them. Returns the pending token (the decode
    input). The caller checks capacity via :func:`can_import` first —
    exhaustion here raises (paging.PagePoolExhausted)."""
    import jax.numpy as jnp
    assert engine.kv_layout == "paged", \
        "page-slice import needs kv_layout 'paged'"
    assert engine.page_size == sl.page_size, \
        "page-size mismatch: engine {} vs slice {}".format(
            engine.page_size, sl.page_size)
    pool_shape = tuple(engine.kv.k.shape[1:])
    assert tuple(sl.k_pages.shape[1:]) == pool_shape, \
        "pool geometry mismatch: engine {} vs slice {}".format(
            pool_shape, tuple(sl.k_pages.shape[1:]))
    assert int(engine.page_counts[slot]) == 0 and \
        int(engine.lengths[slot]) == 0, \
        "import into live slot {}".format(slot)
    page_ids = np.asarray([engine.allocator.alloc()
                           for _ in range(sl.n_pages)], np.int32)
    k = engine.kv.k.at[page_ids].set(
        jnp.asarray(sl.k_pages, engine.kv.k.dtype))
    v = engine.kv.v.at[page_ids].set(
        jnp.asarray(sl.v_pages, engine.kv.v.dtype))
    engine.kv.update((k, v))
    engine.page_tables[slot, :sl.n_pages] = page_ids
    engine.page_counts[slot] = sl.n_pages
    engine.lengths[slot] = sl.length
    return sl.pending_token


def can_import(engine, sl):
    """True when the engine's pool can hold the slice right now (after
    trying prefix-cache eviction, mirroring admission)."""
    need = sl.n_pages
    if not engine.allocator.can_alloc(need) and \
            engine.prefix_cache is not None:
        engine.prefix_cache.evict(need)
    return engine.allocator.can_alloc(need)
