"""Multi-tenant LoRA-style adapters: many model variants, one page pool.

An adapter is a low-rank logits delta over the base model's tied
LM head: for adapter ``a`` with leaves ``A_a (rank, d_model)`` and
``B_a (vocab, rank)``, the served logits become ``hidden @ wte.T +
(hidden @ A_a.T) @ B_a.T * (alpha / rank)``. Adapters are EXTRA
sharded leaves next to the base params — the KV pages they produce are
identical to the base model's (the delta touches only the readout), so
every tenant shares ONE paged pool and one decode program.

Engine integration (inference/engine.py):

  * ``engine.attach_adapters(adapter_set)`` stacks the leaves into
    ``(n_adapters, rank, d_model)`` / ``(n_adapters, vocab, rank)``
    device arrays (row 0 is the all-zero BASE adapter, so serving
    adapter id 0 is the byte-identical oracle for the adapter-aware
    programs);
  * the scheduler assigns each request's adapter id to its slot; the
    fused decode gathers each slot's ``(A, B)`` rows inside the jitted
    program, so one decode step serves a mixed-tenant batch;
  * prefix-cache keys gain the adapter id as a hash namespace
    (paging.PrefixCache ``namespace=``): two tenants with the same
    prompt never cross-hit each other's pages. KV pages are adapter-
    independent here (readout-only delta), but the namespace keeps the
    contract honest for adapters that later grow attention deltas.
"""
import numpy as np


class AdapterSet:
    """Registry of LoRA-style adapter leaves over one base model.

    Adapter id 0 is always the reserved BASE adapter (all-zero delta).
    ``add`` registers a named variant and returns its id; leaves
    default to the classic LoRA init (A random normal, B zero — a
    freshly added adapter serves exactly the base model until its B
    trains away from zero) unless explicit arrays are given.
    """

    def __init__(self, d_model, vocab_size, rank=8, alpha=None, seed=0):
        assert rank >= 1, "adapter rank must be >= 1"
        self.d_model = int(d_model)
        self.vocab_size = int(vocab_size)
        self.rank = int(rank)
        self.alpha = float(alpha if alpha is not None else rank)
        self._rng = np.random.RandomState(seed)
        self._A = [np.zeros((self.rank, self.d_model), np.float32)]
        self._B = [np.zeros((self.vocab_size, self.rank), np.float32)]
        self.names = {"base": 0}

    def __len__(self):
        return len(self._A)

    def add(self, name, A=None, B=None):
        """Register adapter ``name``; returns its integer id."""
        assert name not in self.names, \
            "adapter {!r} already registered".format(name)
        if A is None:
            A = self._rng.normal(
                0.0, 1.0 / self.rank,
                size=(self.rank, self.d_model)).astype(np.float32)
        if B is None:
            B = np.zeros((self.vocab_size, self.rank), np.float32)
        A = np.asarray(A, np.float32)
        B = np.asarray(B, np.float32)
        assert A.shape == (self.rank, self.d_model), \
            "A shape {} != {}".format(A.shape, (self.rank, self.d_model))
        assert B.shape == (self.vocab_size, self.rank), \
            "B shape {} != {}".format(B.shape,
                                      (self.vocab_size, self.rank))
        aid = len(self._A)
        self._A.append(A)
        self._B.append(B)
        self.names[name] = aid
        return aid

    def id_of(self, name):
        return self.names[name]

    def stacked(self, dtype=None, mesh=None):
        """-> device arrays ``(A (n, rank, d_model), B (n, vocab,
        rank))`` with the ``alpha / rank`` LoRA scale folded into B
        (one multiply at stack time instead of every step). Sharded
        like the base params' vocab dim when a mesh is given (extra
        sharded leaves, not a host-side side table)."""
        import jax
        import jax.numpy as jnp
        A = jnp.asarray(np.stack(self._A))
        B = jnp.asarray(np.stack(self._B) * (self.alpha / self.rank))
        if dtype is not None:
            A, B = A.astype(dtype), B.astype(dtype)
        if mesh is not None:
            A, B = jax.device_put(A), jax.device_put(B)
        return A, B

    def logits_delta(self, hidden, adapter_id):
        """Host-side oracle: the delta the jitted path must reproduce
        (fp32 numpy; tests pin the jitted gather against this)."""
        h = np.asarray(hidden, np.float32)
        a = self._A[adapter_id]
        b = self._B[adapter_id] * (self.alpha / self.rank)
        return (h @ a.T) @ b.T
