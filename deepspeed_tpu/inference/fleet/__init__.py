"""Disaggregated serving fleet (docs/inference.md, docs/fleet.md).

Prefill/decode engine roles over the PR 7 paged engine, a serialized
KV page-slice handoff between them (bitwise fp oracle + opt-in
blockwise-int8 wire), an SLO-driven front-end router whose every
decision is a schema-pinned event, and multi-tenant LoRA-style
adapters served from one page pool.
"""
from .adapters import AdapterSet
from .events import (KIND_ROUTER_EVENT, ROUTER_DECISIONS,
                     ROUTER_EVENT_KEYS, ROUTER_EVENTS_JSONL,
                     RouterEventLog, make_router_event,
                     validate_router_event)
from .handoff import (HandoffError, PageSlice, can_import,
                      deserialize_slice, export_slice, import_slice,
                      serialize_slice)
from .roles import DecodeRole, PrefillRole
from .router import FleetRouter
from .serve import DisaggServer

__all__ = [
    "AdapterSet", "DecodeRole", "DisaggServer", "FleetRouter",
    "HandoffError", "KIND_ROUTER_EVENT", "PageSlice", "PrefillRole",
    "ROUTER_DECISIONS", "ROUTER_EVENTS_JSONL", "ROUTER_EVENT_KEYS",
    "RouterEventLog", "can_import", "deserialize_slice", "export_slice",
    "import_slice", "make_router_event", "serialize_slice",
    "validate_router_event",
]
