"""SLO-driven front-end router for the disaggregated serving fleet.

The router is pure host-side policy — no device code. It owns four
decisions, every one of which lands in the schema-pinned event log
(events.py) that ``bin/ds_fleet.py`` surfaces:

  * **enroll / enroll_refusal** — a host joins the fleet only if its
    program fingerprint (analysis/concurrency/divergence.py) matches
    the fleet's reference digest. A divergent host would lower a
    different program family and desynchronize the fleet; it is
    REFUSED, not warned about.
  * **admit / deny** — admission by predicted cost: prompt length maps
    to a prefill bucket, and the router prices each bucket with an
    EWMA of measured prefill walls (the compile observatory's bucket
    discipline: one jit program per bucket, so per-bucket pricing is
    the natural granularity). A request whose predicted TTFT cannot
    meet the ``ttft_slo_s`` budget is denied at the door instead of
    burning the SLO for everyone behind it.
  * **route_away** — decode placement skips hosts the straggler
    detector flagged (``ingest_fleet_report``) or whose ``/healthz``
    went degraded (``observe_healthz``): a flagged host receives NO
    new decode work until its flag clears.
  * **preempt_migrate** — instead of merely warning when a decode host
    degrades mid-stream, the router lifts its youngest decoding slot
    off (roles.DecodeRole.export_request) and re-homes it on a healthy
    host, stream intact.
"""
from .events import RouterEventLog

# EWMA weight for bucket pricing: recent walls dominate (compile-time
# outliers from the first trace wash out after a few requests)
_PRICE_ALPHA = 0.4


class _Host:
    __slots__ = ("name", "kind", "role", "digest", "straggler",
                 "unhealthy", "decode_assignments")

    def __init__(self, name, kind, role, digest):
        self.name = name
        self.kind = kind              # "prefill" | "decode"
        self.role = role              # PrefillRole / DecodeRole / None
        self.digest = digest
        self.straggler = False
        self.unhealthy = False
        self.decode_assignments = 0


class FleetRouter:

    def __init__(self, ttft_slo_s=None, tpot_slo_s=None,
                 admit_budget_factor=1.0, event_dir=None, watchdog=None):
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.admit_budget_factor = float(admit_budget_factor)
        self.events = RouterEventLog(event_dir)
        self.watchdog = watchdog
        self.hosts = {}
        self.reference_digest = None
        self._bucket_price = {}       # bucket -> EWMA prefill seconds
        self.denied = []              # uids denied at the door
        self.migrations = 0

    # ------------------------------------------------------ enrollment

    def enroll(self, name, kind, role=None, fingerprint=None):
        """Enroll a host. ``fingerprint`` is the PR 15 program
        fingerprint dict ({version, digest, families}); the first
        fingerprinted host sets the fleet's reference digest, and any
        later host with a DIFFERENT digest is refused. Returns True on
        enrollment."""
        assert kind in ("prefill", "decode"), kind
        digest = None if fingerprint is None else fingerprint["digest"]
        if digest is not None:
            if self.reference_digest is None:
                self.reference_digest = digest
            elif digest != self.reference_digest:
                self.events.emit(
                    decision="enroll_refusal", host=name,
                    reason="program fingerprint diverges from the "
                           "fleet reference",
                    detail={"digest": digest,
                            "reference": self.reference_digest})
                return False
        self.hosts[name] = _Host(name, kind, role, digest)
        self.events.emit(decision="enroll", host=name,
                         reason="joined as {} host".format(kind),
                         detail={"digest": digest})
        return True

    # --------------------------------------------- health / stragglers

    def mark_straggler(self, name, flagged=True):
        if name in self.hosts:
            self.hosts[name].straggler = bool(flagged)

    def ingest_fleet_report(self, report):
        """Feed a fleet_report (telemetry/fleet/aggregate.merge_run):
        every host named in the straggler flags loses decode
        eligibility until a later report clears it."""
        flagged = {f["host"] for f in
                   (report.get("straggler") or {}).get("flags", [])}
        for host in self.hosts.values():
            host.straggler = host.name in flagged

    def observe_healthz(self, name, payload):
        """Feed one host's /healthz payload (telemetry collector
        healthz()): a degraded status (SLO burn, watchdog trip) marks
        the host unhealthy for decode placement."""
        if name not in self.hosts:
            return
        status = (payload or {}).get("status")
        self.hosts[name].unhealthy = status not in (None, "ok")

    def _eligible_decode(self):
        return [h for h in self.hosts.values()
                if h.kind == "decode" and not h.straggler and
                not h.unhealthy]

    def _flagged_decode(self):
        return [h for h in self.hosts.values()
                if h.kind == "decode" and (h.straggler or h.unhealthy)]

    # -------------------------------------------------------- pricing

    def observe_prefill(self, bucket, seconds):
        """Fold one measured prefill wall into the bucket's EWMA price."""
        prev = self._bucket_price.get(bucket)
        self._bucket_price[bucket] = seconds if prev is None else \
            _PRICE_ALPHA * seconds + (1.0 - _PRICE_ALPHA) * prev

    def predicted_cost(self, prompt_len, bucket_for):
        """Predicted prefill seconds for a prompt: its bucket's EWMA
        price; unpriced buckets interpolate linearly from the nearest
        priced one (cost scales ~linearly with bucket tokens); no
        prices at all -> None (the router admits on faith until the
        first walls land)."""
        bucket = bucket_for(prompt_len)
        price = self._bucket_price.get(bucket)
        if price is not None:
            return price
        if not self._bucket_price:
            return None
        ref_bucket = min(self._bucket_price,
                         key=lambda b: abs(b - bucket))
        return self._bucket_price[ref_bucket] * bucket / ref_bucket

    # ------------------------------------------------------ decisions

    def admit(self, uid, prompt_len, bucket_for, queue_depth=0):
        """Admission by predicted cost against the TTFT SLO budget:
        predicted prefill cost (scaled by the queue ahead) must fit
        ``ttft_slo_s * admit_budget_factor``. No SLO configured, or no
        pricing yet -> always admit."""
        cost = self.predicted_cost(prompt_len, bucket_for)
        budget = None if self.ttft_slo_s is None else \
            self.ttft_slo_s * self.admit_budget_factor
        if budget is not None and cost is not None and \
                cost * (1 + queue_depth) > budget:
            self.events.emit(
                decision="deny", request_uid=uid,
                reason="predicted TTFT {:.4f}s x (1+{} queued) exceeds "
                       "the {:.4f}s budget".format(cost, queue_depth,
                                                   budget),
                predicted_cost_s=cost)
            self.denied.append(uid)
            return False
        self.events.emit(decision="admit", request_uid=uid,
                         reason="within TTFT budget",
                         predicted_cost_s=cost)
        return True

    def observe_ttft(self, seconds):
        """Feed a realized TTFT into the PR 8 ttft_slo watchdog (when
        the fleet shares one)."""
        if self.watchdog is not None:
            self.watchdog.observe_ttft(seconds)

    def pick_decode_host(self, uid=None):
        """Least-loaded eligible decode host (free slots, then fewest
        assignments). Emits one route_away per flagged host that had
        free capacity the router refused to use. Returns the host
        NAME, or None when no eligible host has a free slot."""
        eligible = self._eligible_decode()
        with_slots = [h for h in eligible
                      if h.role is None or h.role.free_slots() > 0]
        for flagged in self._flagged_decode():
            if flagged.role is None or flagged.role.free_slots() > 0:
                self.events.emit(
                    decision="route_away", request_uid=uid,
                    host=flagged.name,
                    reason="straggler-flagged" if flagged.straggler
                    else "healthz degraded")
        if not with_slots:
            return None
        best = min(with_slots,
                   key=lambda h: (-(h.role.free_slots()
                                    if h.role is not None else 0),
                                  h.decode_assignments, h.name))
        best.decode_assignments += 1
        return best.name

    def preempt_migrate(self, src_name, quantize=False):
        """Lift the youngest decoding request off a degraded host and
        re-home it on a healthy one. Returns the migrated request, or
        None when there is no victim or no destination (the event log
        says which)."""
        src = self.hosts[src_name]
        assert src.role is not None, \
            "host {!r} enrolled without a live role object".format(
                src_name)
        victim = src.role.youngest()
        if victim is None:
            return None
        dst_name = self.pick_decode_host(uid=victim.uid)
        if dst_name is None or dst_name == src_name:
            return None
        sl = src.role.export_request(victim, quantize=quantize)
        req = self.hosts[dst_name].role.accept_migrated(sl, victim)
        if req is None:
            # destination filled up between pick and import: put the
            # victim back where it was (its pages still fit there)
            req = src.role.accept_migrated(sl, victim)
            assert req is not None, \
                "migration rollback failed: source host {!r} could " \
                "not re-import its own slice".format(src_name)
            return None
        self.migrations += 1
        self.events.emit(
            decision="preempt_migrate", request_uid=victim.uid,
            host=src_name,
            reason="decode slot migrated off degraded host",
            detail={"to": dst_name,
                    "generated": len(victim.generated)})
        return req

    def decision_counts(self):
        return self.events.decisions()
