"""DisaggServer: the pump that drives a disaggregated serving fleet.

One server owns N prefill roles, M decode roles and a FleetRouter, and
replays the monolithic scheduler's step discipline across them: each
``step()`` admits queued requests through the router's predicted-cost
gate, runs at most one whole-request prefill per prefill host, moves
finished KV over the serialized page-slice wire (every handoff round-
trips through ``serialize_slice``/``deserialize_slice`` — the real
bytes, not an object reference), places the decode through the
router's straggler-aware picker, then fires one scheduler step on
every decode host. Degraded hosts with live streams get their
youngest slot preempt-and-migrated instead of a warning.

Metrics land in ONE shared ServingMetrics (TTFT at first-token from
the prefill half, decode/goodput from the decode halves), so
bench_inference.py's trace harness reads the same snapshot keys it
reads from a monolith.
"""
import time
from collections import deque

from ...utils.monitor import ServingMetrics
from .handoff import DEFAULT_HANDOFF_BLOCK, deserialize_slice
from .roles import DecodeRole, PrefillRole
from .router import FleetRouter

_UNSET = object()


class _Ticket:
    __slots__ = ("uid", "prompt", "max_new_tokens", "eos_token_id",
                 "arrival_t", "req", "denied", "payload", "slice",
                 "first_token_t")

    def __init__(self, uid, prompt, max_new_tokens, eos_token_id,
                 arrival_t):
        self.uid = uid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.arrival_t = arrival_t
        self.req = None            # live decode-side request
        self.denied = False
        self.payload = None        # serialized slice awaiting a host
        self.slice = None
        self.first_token_t = None


class DisaggServer:

    def __init__(self, prefill_engines, decode_engines, metrics=None,
                 sampling=None, quantize=False,
                 block_size=DEFAULT_HANDOFF_BLOCK, router=None,
                 ttft_slo_s=None, tpot_slo_s=None,
                 admit_budget_factor=1.0, event_dir=None,
                 fingerprints=None, watchdog=None):
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.quantize = bool(quantize)
        self.router = router if router is not None else FleetRouter(
            ttft_slo_s=ttft_slo_s, tpot_slo_s=tpot_slo_s,
            admit_budget_factor=admit_budget_factor,
            event_dir=event_dir, watchdog=watchdog)
        fingerprints = fingerprints or {}
        self.prefill_roles = {}
        for name, engine in prefill_engines.items():
            role = PrefillRole(engine, sampling=sampling,
                               quantize=quantize, block_size=block_size)
            if self.router.enroll(name, "prefill", role=role,
                                  fingerprint=fingerprints.get(name)):
                self.prefill_roles[name] = role
        self.decode_roles = {}
        for name, engine in decode_engines.items():
            role = DecodeRole(engine, metrics=self.metrics,
                              sampling=sampling)
            if self.router.enroll(name, "decode", role=role,
                                  fingerprint=fingerprints.get(name)):
                self.decode_roles[name] = role
        assert self.prefill_roles and self.decode_roles, \
            "a disaggregated fleet needs at least one enrolled " \
            "prefill host and one enrolled decode host"
        self.queue = deque()
        self.pending = deque()     # tickets with a payload, no host yet
        self.tickets = {}
        self._next_uid = 0
        self.steps = 0

    # ------------------------------------------------------------ intake

    def submit(self, prompt, max_new_tokens=None, eos_token_id=_UNSET,
               arrival_t=None):
        """Queue a request; returns its ticket uid."""
        prompt = [int(t) for t in prompt]
        assert len(prompt) >= 1, "empty prompt"
        ticket = _Ticket(
            self._next_uid, prompt, max_new_tokens,
            eos_token_id if eos_token_id is not _UNSET else _UNSET,
            arrival_t if arrival_t is not None else time.perf_counter())
        self._next_uid += 1
        self.tickets[ticket.uid] = ticket
        self.queue.append(ticket)
        return ticket.uid

    @property
    def has_work(self):
        if self.queue or self.pending:
            return True
        if any(role.has_work for role in self.decode_roles.values()):
            return True
        return any(t.req is not None and t.req.state != "done"
                   for t in self.tickets.values())

    @property
    def preemptions(self):
        return sum(r.sched.preemptions
                   for r in self.decode_roles.values())

    # ------------------------------------------------------------ phases

    def _bucket_for(self):
        return next(iter(self.prefill_roles.values())).engine.bucket_for

    def _admit_and_prefill(self):
        bucket_for = self._bucket_for()
        for role in self.prefill_roles.values():
            # the router's cost gate first: denied requests never cost
            # a prefill slot
            while self.queue:
                ticket = self.queue[0]
                if self.router.admit(ticket.uid, len(ticket.prompt),
                                     bucket_for,
                                     queue_depth=len(self.queue) - 1):
                    break
                self.queue.popleft()
                ticket.denied = True
            if not self.queue:
                return
            ticket = self.queue[0]
            out = role.prefill_request(ticket.prompt,
                                       metrics=self.metrics)
            if out is None:
                return                     # pool full: stay queued
            self.queue.popleft()
            payload, _token, dt, bucket = out
            self.router.observe_prefill(bucket, dt)
            ticket.first_token_t = time.perf_counter()
            ttft = ticket.first_token_t - ticket.arrival_t
            self.metrics.record_ttft(ttft)
            self.router.observe_ttft(ttft)
            ticket.payload = payload
            self.pending.append(ticket)

    def _place_handoffs(self):
        for _ in range(len(self.pending)):
            ticket = self.pending[0]
            if ticket.slice is None:
                # the wire round-trip happens exactly once per handoff
                ticket.slice = deserialize_slice(ticket.payload)
                ticket.payload = None
            host = self.router.pick_decode_host(uid=ticket.uid)
            if host is None:
                return                     # no capacity: retry next step
            kwargs = {}
            if ticket.max_new_tokens is not None:
                kwargs["max_new_tokens"] = ticket.max_new_tokens
            if ticket.eos_token_id is not _UNSET:
                kwargs["eos_token_id"] = ticket.eos_token_id
            req = self.decode_roles[host].accept(ticket.slice, **kwargs)
            if req is None:
                return
            req.arrival_t = ticket.arrival_t
            req.first_token_t = ticket.first_token_t
            ticket.req = req
            ticket.slice = None
            self.pending.popleft()

    def _migrate_degraded(self):
        """One preempt-and-migrate per degraded host per step (instead
        of a straggler warning): its youngest decode slot moves to a
        healthy host, stream intact."""
        for host in list(self.router.hosts.values()):
            if host.kind != "decode":
                continue
            if not (host.straggler or host.unhealthy):
                continue
            if host.role is not None and host.role.youngest() is not None:
                self.router.preempt_migrate(host.name,
                                            quantize=self.quantize)

    def step(self):
        """Admit -> prefill+handoff -> place -> migrate-degraded ->
        one decode step per host."""
        self._admit_and_prefill()
        self._place_handoffs()
        self._migrate_degraded()
        for role in self.decode_roles.values():
            if role.has_work:
                role.step()
        self.steps += 1

    def run(self):
        """Drive step() until every ticket resolved. Returns
        ``{ticket_uid: generated tokens}`` — denied tickets map to
        None (the router's event log says why)."""
        while self.has_work:
            self.step()
        out = {}
        for uid, ticket in self.tickets.items():
            if ticket.denied:
                out[uid] = None
            else:
                assert ticket.req is not None and \
                    ticket.req.state == "done", \
                    "ticket {} never completed".format(uid)
                out[uid] = list(ticket.req.generated)
        return out

    # --------------------------------------------------------- reporting

    def handoff_stats(self):
        return {
            "handoffs": sum(r.handoffs
                            for r in self.prefill_roles.values()),
            "payload_bytes": sum(r.handoff_bytes
                                 for r in self.prefill_roles.values()),
            "quantized": self.quantize,
            "migrations": self.router.migrations,
        }
