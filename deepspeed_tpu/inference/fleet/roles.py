"""Engine roles for disaggregated serving: prefill and decode.

A **prefill** role runs chunked prefill for one request at a time on
its own paged engine, samples the first token, exports the finished KV
as a serialized page-table slice (handoff.py), and immediately frees
the slot — its pool only ever holds in-flight prompts. A **decode**
role imports slices into its own pool and continues decoding through
the standard continuous-batching scheduler, so preemption, speculative
decode and telemetry all behave exactly as on a monolithic engine.

The contract the dryrun leg pins: greedy streams through
``PrefillRole.prefill_request`` → bytes → ``DecodeRole.accept`` are
byte-identical to the single-engine paged path (fp handoff), because
the prefill programs are the same jitted programs, the fp codec moves
page payloads verbatim, and the decode gather reads them through the
imported page table at identical positions.
"""
import time

from ..scheduler import ContinuousBatchingScheduler, InferenceRequest
from ..paging import plan_chunks
from .handoff import (DEFAULT_HANDOFF_BLOCK, can_import, export_slice,
                      import_slice, serialize_slice)

_UNSET = object()


class PrefillRole:
    """Chunked-prefill front half over a paged :class:`InferenceEngine`."""

    def __init__(self, engine, sampling=None, quantize=False,
                 block_size=DEFAULT_HANDOFF_BLOCK):
        assert engine.kv_layout == "paged", \
            "the prefill role needs kv_layout 'paged' (page-table " \
            "slices are its export format)"
        self.engine = engine
        self.sampling = sampling
        self.quantize = bool(quantize)
        self.block_size = int(block_size)
        engine.serving_role = "prefill"
        self._free = list(range(engine.num_slots))
        self.handoffs = 0
        self.handoff_bytes = 0

    def prefill_request(self, prompt, metrics=None):
        """Prefill ``prompt`` end to end and export its KV. Returns
        ``(payload_bytes, first_token, prefill_seconds, bucket)`` or
        None when the pool/slots cannot admit right now (the router
        keeps the request queued)."""
        engine = self.engine
        prompt = [int(t) for t in prompt]
        if not self._free:
            return None
        slot = self._free[-1]
        if not engine.try_admit(slot, prompt):
            return None
        self._free.pop()
        ic = engine.inference_config
        # the request's trace STARTS here: the root's trace_id rides the
        # page-slice header so the decode host's spans continue the SAME
        # trace (one request = one trace across role processes)
        tel = engine.telemetry
        spans = tel.spans if tel is not None else None
        span = None
        if spans is not None:
            span = spans.begin("prefill_request", role="prefill",
                               prompt_tokens=len(prompt))
        t0 = time.perf_counter()
        start = engine.match_prefix(slot, prompt)
        if start:
            engine.lengths[slot] = start
        chunks = plan_chunks(
            len(prompt) - start, ic.prefill_chunk_tokens,
            engine.bucket_for, engine.max_seq_len, start=start,
            max_chunk=engine.prefill_buckets[-1])
        token = None
        for c_start, c_len in chunks:
            c_t0 = time.time()
            token = engine.prefill_chunk(
                slot, prompt[c_start:c_start + c_len], c_start,
                sampling=self.sampling)
            engine.register_prefix(slot, prompt[:c_start + c_len])
            if span is not None:
                span.timed_child("prefill_chunk", c_t0, time.time(),
                                 start=c_start, tokens=c_len)
        dt = time.perf_counter() - t0
        if metrics is not None:
            metrics.record_prefill(len(prompt) - start, dt)
            if engine.telemetry is not None:
                # one role="prefill" serving_step per finished prefill,
                # through the same sink layer the decode schedulers
                # write — the fleet doctor's per-role host attribution
                # reads these (docs/fleet.md)
                busy = engine.num_slots - len(self._free)
                engine.telemetry.emit_serving_step(
                    step=engine.serving_record_steps, metrics=metrics,
                    active_slots=busy, queue_depth=0,
                    occupancy=busy / engine.num_slots,
                    page_pool=engine.page_pool_stats(),
                    prefix=engine.prefix_stats(), role="prefill")
                engine.serving_record_steps += 1
        sl = export_slice(engine, slot, context=prompt,
                          pending_token=token,
                          trace_id=span.trace_id
                          if span is not None else None)
        payload = serialize_slice(sl, quantize=self.quantize,
                                  block_size=self.block_size)
        engine.free_slot(slot)
        self._free.append(slot)
        self.handoffs += 1
        self.handoff_bytes += len(payload)
        if span is not None:
            span.event("handoff_export", bytes=len(payload),
                       pages=sl.n_pages)
            span.end()
        return payload, int(token), dt, engine.bucket_for(len(prompt))


class DecodeRole:
    """Decode back half: a continuous-batching scheduler whose requests
    arrive as imported page slices instead of prompts."""

    def __init__(self, engine, metrics=None, sampling=None):
        assert engine.kv_layout == "paged", \
            "the decode role needs kv_layout 'paged' (it imports " \
            "page-table slices)"
        self.engine = engine
        engine.serving_role = "decode"
        self.sched = ContinuousBatchingScheduler(engine, metrics=metrics,
                                                 sampling=sampling)
        self.accepted = 0

    def _free_slot(self):
        for slot, req in enumerate(self.sched.slots):
            if req is None:
                return slot
        return None

    def free_slots(self):
        return sum(1 for r in self.sched.slots if r is None)

    @property
    def active(self):
        return self.sched.num_active

    @property
    def has_work(self):
        return self.sched.has_work

    def step(self):
        return self.sched.step()

    def accept(self, sl, max_new_tokens=None, eos_token_id=_UNSET):
        """Import one deserialized :class:`handoff.PageSlice` and start
        decoding it. Returns the live :class:`InferenceRequest` (its
        ``generated`` list IS the stream; ``state == "done"`` when
        retired), or None when no slot/pages are available — the
        router keeps the handoff queued."""
        engine = self.engine
        slot = self._free_slot()
        if slot is None or not can_import(engine, sl):
            return None
        ic = engine.inference_config
        req = InferenceRequest(
            self.sched._next_uid, sl.context,
            max_new_tokens if max_new_tokens is not None
            else ic.max_new_tokens,
            ic.eos_token_id if eos_token_id is _UNSET else eos_token_id)
        self.sched._next_uid += 1
        pending = import_slice(engine, slot, sl)
        req.slot = slot
        req.state = "decode"
        req.admit_order = self.sched._admitted
        self.sched._admitted += 1
        req.first_token_t = time.perf_counter()
        self.sched.slots[slot] = req
        if self.sched._spans is not None:
            # continue the prefill host's trace (sl.trace_id from the
            # slice header; None mints a fresh one) — ds_fleet's merged
            # view shows the request as ONE lane across both roles
            req.span = self.sched._spans.begin(
                "serving_request", trace_id=sl.trace_id, uid=req.uid,
                prompt_tokens=len(sl.context), role="decode")
            req.span.event("handoff_accept", slot=slot,
                           pages=sl.n_pages)
        if engine.drafter is not None:
            engine.drafter.prefill(slot, req.context)
        self.accepted += 1
        # the handed-off first token enters through the same EOS/budget
        # gate a monolith's prefill token does (may retire immediately)
        self.sched._append_tokens(req, [pending])
        return req

    def accept_migrated(self, sl, req):
        """Re-home a live request mid-stream (preempt-and-migrate):
        import its slice and keep its identity — uid, generated tokens,
        budget — so the stream continues where the source host stopped.
        Returns the request, or None when this host has no capacity."""
        engine = self.engine
        slot = self._free_slot()
        if slot is None or not can_import(engine, sl):
            return None
        import_slice(engine, slot, sl)
        req.slot = slot
        req.state = "decode"
        req.admit_order = self.sched._admitted
        self.sched._admitted += 1
        self.sched.slots[slot] = req
        if engine.drafter is not None:
            engine.drafter.prefill(slot, req.context)
        self.accepted += 1
        return req

    def export_request(self, req, quantize=False,
                       block_size=DEFAULT_HANDOFF_BLOCK):
        """Lift a live decoding request OFF this host (the migration
        source side): export its pages + pending token, release the
        slot. The caller re-homes the returned slice via another
        host's :meth:`accept_migrated`."""
        engine = self.engine
        assert req.slot is not None and \
            self.sched.slots[req.slot] is req, \
            "request {} is not live on this host".format(req.uid)
        assert req.state == "decode" and req.generated, \
            "only decoding requests migrate (state {!r})".format(
                req.state)
        # generated[-1] is the PENDING token (not yet in the cache) —
        # the same discipline recompute-preemption uses
        sl = export_slice(
            engine, req.slot,
            context=req.prompt + req.generated[:-1],
            pending_token=req.generated[-1])
        self.sched.slots[req.slot] = None
        engine.free_slot(req.slot)
        if engine.drafter is not None:
            engine.drafter.free_slot(req.slot)
        req.slot = None
        return sl

    def youngest(self):
        """The most recently admitted decoding request (the preempt-
        and-migrate victim policy, matching recompute-preemption's)."""
        victim = None
        for req in self.sched.slots:
            if req is None or req.state != "decode":
                continue
            if victim is None or req.admit_order > victim.admit_order:
                victim = req
        return victim
