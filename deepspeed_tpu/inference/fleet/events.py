"""Router-decision event schema for the disaggregated serving fleet.

Every decision the front-end router takes — admit/deny by predicted
cost, route-away from a straggler-flagged host, preempt-and-migrate of
a decode slot, host enrollment and its fingerprint refusal — lands as
ONE schema-pinned JSON event, appended to ``router_events.jsonl``
inside the router's telemetry job directory. The fleet merger
(telemetry/fleet/aggregate.py) reads the per-host files the same way
it reads rescale events and surfaces them in the fleet report's
``router`` section (bin/ds_fleet.py prints the decision table).

Stdlib-only by contract: ``aggregate.py`` and ``check_bench_schema.py``
carry local copies of :data:`ROUTER_EVENT_KEYS` /
:data:`ROUTER_DECISIONS` (pinned equal by
tests/unit/test_serving_fleet.py) so doctoring a crashed run never
needs jax importable.
"""
import json
import os
import time

KIND_ROUTER_EVENT = "router_event"

# per-host file name inside a telemetry job directory (the rescale-
# events discipline: one JSONL per host, merged wall-ordered)
ROUTER_EVENTS_JSONL = "router_events.jsonl"

# the decision vocabulary — the router emits nothing outside this set
ROUTER_DECISIONS = ("admit", "deny", "route_away", "preempt_migrate",
                    "enroll", "enroll_refusal")

# every router_event carries exactly these top-level keys
ROUTER_EVENT_KEYS = ("kind", "wall", "decision", "request_uid", "host",
                     "reason", "predicted_cost_s", "detail")


def make_router_event(*, decision, request_uid=None, host=None,
                      reason="", predicted_cost_s=None, detail=None,
                      wall=None):
    return {
        "kind": KIND_ROUTER_EVENT,
        "wall": float(wall if wall is not None else time.time()),
        "decision": str(decision),
        "request_uid": None if request_uid is None else int(request_uid),
        "host": None if host is None else str(host),
        "reason": str(reason),
        "predicted_cost_s": (None if predicted_cost_s is None
                             else float(predicted_cost_s)),
        "detail": detail,
    }


def validate_router_event(ev):
    """Schema check for one router_event dict. Returns a list of
    problem strings; empty list = valid."""
    problems = []
    if not isinstance(ev, dict):
        return ["router event is not a dict: {!r}".format(
            type(ev).__name__)]
    for key in ROUTER_EVENT_KEYS:
        if key not in ev:
            problems.append("missing key {!r}".format(key))
    extra = sorted(set(ev) - set(ROUTER_EVENT_KEYS))
    if extra:
        problems.append("unexpected key(s) {}".format(extra))
    if problems:
        return problems
    if ev["kind"] != KIND_ROUTER_EVENT:
        problems.append("kind is {!r}, want {!r}".format(
            ev["kind"], KIND_ROUTER_EVENT))
    if ev["decision"] not in ROUTER_DECISIONS:
        problems.append("decision {!r} not in {}".format(
            ev["decision"], ROUTER_DECISIONS))
    if isinstance(ev["wall"], bool) or \
            not isinstance(ev["wall"], (int, float)):
        problems.append("wall is not a number: {!r}".format(ev["wall"]))
    if ev["request_uid"] is not None and (
            isinstance(ev["request_uid"], bool) or
            not isinstance(ev["request_uid"], int)):
        problems.append("request_uid is neither null nor an int: "
                        "{!r}".format(ev["request_uid"]))
    if ev["host"] is not None and not isinstance(ev["host"], str):
        problems.append("host is neither null nor a string: "
                        "{!r}".format(ev["host"]))
    if ev["predicted_cost_s"] is not None and (
            isinstance(ev["predicted_cost_s"], bool) or
            not isinstance(ev["predicted_cost_s"], (int, float))):
        problems.append("predicted_cost_s is neither null nor a number: "
                        "{!r}".format(ev["predicted_cost_s"]))
    if ev["detail"] is not None and not isinstance(ev["detail"], dict):
        problems.append("detail is neither null nor a dict: "
                        "{!r}".format(ev["detail"]))
    return problems


class RouterEventLog:
    """In-memory event list + optional JSONL append (one line per
    decision, flushed per event so a crashed router leaves every
    decision it took on disk — the torn-tail tolerance lives in the
    merger's ``read_jsonl_tolerant``)."""

    def __init__(self, output_dir=None):
        self.events = []
        self.path = None
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            self.path = os.path.join(output_dir, ROUTER_EVENTS_JSONL)

    def emit(self, **kwargs):
        ev = make_router_event(**kwargs)
        problems = validate_router_event(ev)
        assert not problems, "router event failed its own schema: " \
            "{}".format(problems)
        self.events.append(ev)
        if self.path is not None:
            with open(self.path, "a") as fh:
                fh.write(json.dumps(ev) + "\n")
                fh.flush()
        return ev

    def decisions(self):
        """{decision: count} over everything emitted so far."""
        counts = {}
        for ev in self.events:
            counts[ev["decision"]] = counts.get(ev["decision"], 0) + 1
        return counts
