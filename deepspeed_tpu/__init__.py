"""DeepSpeed-TPU: a TPU-native training framework with the DeepSpeed API.

Public surface parity with reference deepspeed/__init__.py: ``initialize()``,
``add_config_arguments()``, ``init_distributed``, ``zero``, pipeline module
types, ops. Internals are JAX/XLA/pjit/Pallas over a device mesh — no
torch, no NCCL.
"""
from .version import __version__, __version_info__

from .utils.distributed import init_distributed
from .utils.logging import logger, log_dist
from .runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from .runtime.activation_checkpointing import checkpointing
from . import zero

try:
    from .git_version_info import git_hash as __git_hash__, \
        git_branch as __git_branch__
except ImportError:
    __git_hash__ = None
    __git_branch__ = None


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None,
               dist_init_required=None, collate_fn=None, config=None,
               config_params=None):
    """Initialize the DeepSpeed-TPU engine.

    Mirrors reference deepspeed/__init__.py:52. Returns a tuple of
    ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    ``model`` is a :class:`deepspeed_tpu.Model` (apply_fn + params pytree), a
    flax module instance paired with params via ``model_parameters``, or a
    :class:`deepspeed_tpu.pipe.PipelineModule` for pipeline parallelism.
    """
    from .runtime.engine import DeepSpeedEngine
    try:
        from .runtime.pipe.module import PipelineModule
        from .runtime.pipe.engine import PipelineEngine
    except ImportError:  # pipeline stack not built yet
        PipelineModule = ()
        PipelineEngine = None

    assert model is not None, "deepspeed.initialize requires a model"

    log_dist("DeepSpeedTPU info: version={}".format(__version__), ranks=[0])

    if dist_init_required is None or dist_init_required:
        init_distributed()

    if config is None and config_params is not None:
        config = config_params

    if not isinstance(model, PipelineModule):
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config_params=config)
    else:
        assert mpu is None, "mpu must be None with pipeline parallelism"
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=model.mpu(),
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config_params=config)

    return_items = [engine, engine.optimizer, engine.training_dataloader,
                    engine.lr_scheduler]
    return tuple(return_items)


def _add_core_arguments(parser):
    """Add DeepSpeed args group (reference __init__.py:148)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no "
                            "impact on DeepSpeed backend)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; discover the job launch info from "
                            "the MPI environment.")
    return parser


def add_config_arguments(parser):
    """Update an argument parser to enable the DeepSpeed-TPU runtime
    (reference __init__.py:199)."""
    parser = _add_core_arguments(parser)
    return parser
