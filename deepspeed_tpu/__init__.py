"""DeepSpeed-TPU: a TPU-native training framework with the DeepSpeed API.

Public surface parity with reference deepspeed/__init__.py: ``initialize()``,
``add_config_arguments()``, ``init_distributed``, ``zero``, pipeline module
types, ops. Internals are JAX/XLA/pjit/Pallas over a device mesh — no
torch, no NCCL.
"""
from .version import __version__, __version_info__

from .utils.distributed import init_distributed
from .utils.logging import logger, log_dist
from .runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from .runtime.activation_checkpointing import checkpointing
from . import zero

try:
    from .git_version_info import git_hash as __git_hash__, \
        git_branch as __git_branch__
except ImportError:
    __git_hash__ = None
    __git_branch__ = None


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None,
               dist_init_required=None, collate_fn=None, config=None,
               config_params=None):
    """Initialize the DeepSpeed-TPU engine.

    Mirrors reference deepspeed/__init__.py:52. Returns a tuple of
    ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    ``model`` is a :class:`deepspeed_tpu.Model` (apply_fn + params pytree), a
    flax module instance paired with params via ``model_parameters``, or a
    :class:`deepspeed_tpu.pipe.PipelineModule` for pipeline parallelism.
    """
    from .runtime.engine import DeepSpeedEngine
    try:
        from .runtime.pipe.module import PipelineModule
        from .runtime.pipe.engine import PipelineEngine
    except ImportError:  # pipeline stack not built yet
        PipelineModule = ()
        PipelineEngine = None

    assert model is not None, "deepspeed.initialize requires a model"

    log_dist("DeepSpeedTPU info: version={}".format(__version__), ranks=[0])

    if dist_init_required is None or dist_init_required:
        init_distributed()

    if config is None and config_params is not None:
        config = config_params

    if not isinstance(model, PipelineModule):
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config_params=config)
    else:
        assert mpu is None, "mpu must be None with pipeline parallelism"
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=model.mpu(),
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config_params=config)

    return_items = [engine, engine.optimizer, engine.training_dataloader,
                    engine.lr_scheduler]
    return tuple(return_items)


def init_inference(model=None, config=None, mp_size=1, mesh=None,
                   dtype=None, injection_policy=None,
                   replace_method="auto", seed=0, draft_model=None,
                   audit=False):
    """Initialize the DeepSpeed-TPU inference engine.

    Mirrors reference ``deepspeed.init_inference(model, mp_size, dtype,
    injection_policy, replace_method, ...)`` alongside :func:`initialize`.
    Returns an :class:`deepspeed_tpu.inference.InferenceEngine` with a
    preallocated slot-based KV cache, jitted prefill/decode paths and a
    continuous-batching scheduler (``engine.generate(prompts)``).

    ``model`` is a :class:`deepspeed_tpu.Model` carrying a GPT2Config at
    ``.config`` (``models.gpt2.make_gpt2_model``). ``config`` is a
    ds_config dict/path whose ``inference`` section sets max_batch_size,
    max_seq_len, prefill_buckets, dtype and sampling defaults. ``mp_size``
    > 1 (or an explicit ``mesh`` with a ``model`` axis) shards params with
    the model's Megatron partition specs and the KV cache over its heads
    axis. When ``replace_method`` is truthy (default "auto") and
    ``model.params`` is an HF-flax GPT-2 tree (a ``transformer`` subtree),
    the params are converted IN PLACE via
    ``module_inject.hf_gpt2_to_gpt2_params`` using ``injection_policy``
    (default ``HFGPT2LayerPolicy``) — mirroring the reference's
    module-mutating injection.

    ``inference.kv_layout: "paged"`` switches the engine to the paged KV
    cache (+ ``prefix_caching``, ``speculative`` — docs/inference.md);
    ``draft_model`` supplies the small GPT-2 drafter that
    ``inference.speculative.method: "model"`` requires.

    ``audit=True`` runs the ahead-of-time shard-lint
    (``engine.audit()``, docs/analysis.md) over the prefill/decode/
    spec-verify programs before the engine is returned — findings warn,
    or raise when the config sets ``analysis.strict``.
    """
    from .inference.engine import InferenceEngine

    assert model is not None, "deepspeed.init_inference requires a model"

    params = getattr(model, "params", None)
    if replace_method and isinstance(params, dict):
        tree = params.get("params", params)
        if isinstance(tree, dict) and "transformer" in tree:
            from .module_inject import (hf_gpt2_to_gpt2_params,
                                        HFGPT2LayerPolicy)
            model.params = hf_gpt2_to_gpt2_params(
                params, policy=injection_policy or HFGPT2LayerPolicy)

    log_dist("DeepSpeedTPU inference info: version={}".format(__version__),
             ranks=[0])

    if mesh is None and mp_size > 1:
        from .parallel.topology import build_mesh
        import jax
        assert jax.device_count() % mp_size == 0, \
            "mp_size {} does not divide device count {}".format(
                mp_size, jax.device_count())
        mesh = build_mesh(data=jax.device_count() // mp_size, model=mp_size)

    engine = InferenceEngine(model, config=config, mesh=mesh, dtype=dtype,
                             seed=seed, draft_model=draft_model)
    if audit:
        engine.audit()
    return engine


def _add_core_arguments(parser):
    """Add DeepSpeed args group (reference __init__.py:148)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no "
                            "impact on DeepSpeed backend)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; discover the job launch info from "
                            "the MPI environment.")
    return parser


def add_config_arguments(parser):
    """Update an argument parser to enable the DeepSpeed-TPU runtime
    (reference __init__.py:199)."""
    parser = _add_core_arguments(parser)
    return parser
