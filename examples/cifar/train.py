"""CIFAR-style classifier with deepspeed_tpu (reference
DeepSpeedExamples/cifar — BASELINE config 1 shape).

Run: python examples/cifar/train.py --deepspeed_config examples/cifar/ds_config.json
Uses synthetic CIFAR-shaped data so the example is hermetic; swap
``SyntheticCifar`` for a real dataset loader to train for real.
"""
import argparse

try:
    import deepspeed_tpu as deepspeed
except ImportError:  # running from a source checkout without install
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    import deepspeed_tpu as deepspeed

import numpy as np
import jax.numpy as jnp

from deepspeed_tpu.runtime.model import Model


class SyntheticCifar:
    """(3,32,32) images, 10 classes."""

    def __init__(self, n=2048, seed=0):
        rs = np.random.RandomState(seed)
        self.x = rs.randn(n, 3, 32, 32).astype(np.float32)
        self.y = rs.randint(0, 10, size=(n,))

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def make_model(seed=0):
    rs = np.random.RandomState(seed)
    d_in, d_h = 3 * 32 * 32, 256
    params = {
        "w1": jnp.asarray(rs.randn(d_in, d_h) * (1.0 / np.sqrt(d_in))),
        "b1": jnp.zeros(d_h),
        "w2": jnp.asarray(rs.randn(d_h, 10) * (1.0 / np.sqrt(d_h))),
        "b2": jnp.zeros(10),
    }

    def apply_fn(p, x, y):
        import jax
        h = jnp.tanh(x.reshape(x.shape[0], -1) @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    return Model(apply_fn, params)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser = deepspeed.add_config_arguments(parser)
    args = parser.parse_args()

    engine, _, loader, _ = deepspeed.initialize(
        args=args, model=make_model(), training_data=SyntheticCifar(),
        config_params=args.deepspeed_config)

    for epoch in range(args.epochs):
        for x, y in loader:
            loss = engine(jnp.asarray(x), jnp.asarray(y))
            engine.backward(loss)
            engine.step()
        print("epoch {} loss {:.4f}".format(epoch, float(loss)))


if __name__ == "__main__":
    main()
