"""Megatron-GPT2 pretraining with deepspeed_tpu (reference
DeepSpeedExamples/Megatron-LM — BASELINE configs 2/4/5 shape).

Run (synthetic data):
  python examples/gpt2/pretrain.py --size gpt2_small \
      --deepspeed_config examples/gpt2/ds_config_zero2.json --steps 50

Run (real tokens via the native mmap dataset + prefetch loader):
  python examples/gpt2/pretrain.py --data_prefix /path/to/corpus ...
where corpus.bin/.idx were written by
deepspeed_tpu.runtime.data.IndexedDatasetBuilder.
"""
import argparse

try:
    import deepspeed_tpu as deepspeed
except ImportError:  # running from a source checkout without install
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    import deepspeed_tpu as deepspeed

import numpy as np
import jax.numpy as jnp

from deepspeed_tpu.models import gpt2


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", default="gpt2_small",
                        choices=sorted(gpt2.SIZES))
    parser.add_argument("--seq_len", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--data_prefix", default=None,
                        help=".bin/.idx token dataset prefix (default: "
                             "synthetic random tokens)")
    parser = deepspeed.add_config_arguments(parser)
    args = parser.parse_args()

    model = gpt2.make_gpt2_model(size=args.size, max_seq_len=args.seq_len)
    engine, _, _, _ = deepspeed.initialize(
        args=args, model=model, config_params=args.deepspeed_config)

    mb = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    gas = engine.gradient_accumulation_steps()

    if args.data_prefix:
        from deepspeed_tpu.runtime.data import (IndexedDataset,
                                                NativePrefetchLoader)
        loader = NativePrefetchLoader(IndexedDataset(args.data_prefix),
                                      batch_size=gas * mb,
                                      seq_len=args.seq_len)

        def next_batch(_):
            ids = next(loader).reshape(gas, mb, args.seq_len)
            return ids
    else:
        rs = np.random.RandomState(0)

        def next_batch(_):
            return rs.randint(0, model.config.vocab_size,
                              size=(gas, mb, args.seq_len)).astype(np.int32)

    for step in range(args.steps):
        ids = next_batch(step)
        loss = engine.train_batch(batch=(ids, ids.copy()))
        if step % 10 == 0:
            print("step {} loss {:.4f}".format(step, float(loss)))


if __name__ == "__main__":
    main()
